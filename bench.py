"""Headline benchmarks on one chip: ResNet-50 ImageNet training throughput
(primary metric) and Transformer-base WMT training throughput (extra metric).

Prints ONE JSON line:
  {"metric": "resnet50_images_per_sec_per_chip", "value": N, "unit": "images/sec",
   "vs_baseline": R, "mfu": F, "extra_metrics": [{"metric":
   "transformer_tokens_per_sec_per_chip", ...}]}

Baselines (reference = PaddlePaddle Fluid 0.15, benchmark/fluid README era):
ResNet-50 ~340 images/sec on a V100 (batch 128, best config) and
Transformer-base ~4.5k tokens/sec/GPU.  vs_baseline = ours / baseline.

Any failure — backend init, compile, runtime — still prints one JSON line,
with an "error" field, so the driver never records an empty round.
"""
from __future__ import annotations

import json
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

BASELINE_IMAGES_PER_SEC = 340.0
BASELINE_TOKENS_PER_SEC = 4500.0
V5E_PEAK_BF16_FLOPS = 197e12  # per chip


def _init_backend(retries=3, delay=15.0, probe_timeout=180.0):
    """jax.devices() with bounded retry AND a watchdog: a wedged TPU tunnel
    makes backend init *hang* (not raise), which must still become an error
    JSON line rather than a silent driver timeout (round-1 failure mode)."""
    import threading

    result = {}

    def probe():
        try:
            import jax

            devs = jax.devices()
            result["on_tpu"] = any(
                d.platform in ("tpu", "axon") or "TPU" in str(d) for d in devs)
            result["version"] = jax.__version__
        except Exception as e:  # noqa: BLE001
            result["error"] = e

    last = None
    for attempt in range(retries):
        t = threading.Thread(target=probe, daemon=True)
        t.start()
        t.join(probe_timeout)
        if t.is_alive():
            # backend init is stuck; the hung thread can't be killed, and a
            # second jax init attempt in this process would block on the
            # same lock — give up loudly
            raise RuntimeError(
                "backend init hung for %.0fs (TPU tunnel wedged?)" % probe_timeout)
        if "on_tpu" in result:
            return result["version"], result["on_tpu"]
        last = result.pop("error", None)
        if attempt < retries - 1:
            time.sleep(delay * (attempt + 1))
    raise RuntimeError("backend init failed after %d attempts: %s" % (retries, last))


def _leg_compiler_options(leg_metric):
    """Per-leg TPU compiler options from ``bench_compiler_options.json``
    (keyed by metric name) — the landing place for tools/bench_resnet_flags.py
    sweep wins.  Options must ride ``.compile(compiler_options=...)``: under
    axon remote compile the SERVER's XLA parses them; env XLA_FLAGS can't
    carry TPU flags here (local jaxlib rejects unknown flags fatally)."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "bench_compiler_options.json")
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            cfg = json.load(f)
        return cfg.get(leg_metric) or None
    except Exception as e:  # noqa: BLE001
        # a malformed file must NOT silently drop the tuned flags — the
        # bench would then publish untuned numbers labeled as tuned
        print("WARNING: bench_compiler_options.json unreadable (%s); "
              "running WITHOUT tuned compiler options" % e, file=sys.stderr)
        return None


def _jit_step(step, leg_metric):
    """jax.jit with donation; when the leg has compiler options on file,
    compile explicitly with them (first call) instead of the jit cache."""
    import jax

    jitted = jax.jit(step, donate_argnums=(0,))
    opts = _leg_compiler_options(leg_metric)
    if not opts:
        return jitted
    cell = {}

    def run(state, feeds):
        c = cell.get("c")
        if c is None:
            c = cell["c"] = jitted.lower(state, feeds).compile(
                compiler_options=opts)
        return c(state, feeds)

    return run


def _time_steps(jitted, state, feeds, iters, warmup=3):
    for _ in range(warmup):
        fetches, state = jitted(state, feeds)
    np.asarray(fetches[0])
    t0 = time.perf_counter()
    for _ in range(iters):
        fetches, state = jitted(state, feeds)
    np.asarray(fetches[0])  # device->host read: true sync even through the tunnel
    dt = time.perf_counter() - t0
    return dt, state


def bench_resnet(on_tpu):
    import jax

    import paddle_tpu as fluid
    from paddle_tpu.jax_bridge import init_state, program_to_fn
    from paddle_tpu.models import resnet

    batch = 128 if on_tpu else 8
    dtype = "bfloat16" if on_tpu else "float32"
    image_shape = (3, 224, 224)

    with fluid.unique_name.guard():
        model = resnet.get_model(
            batch_size=batch, class_dim=1000, depth=50, image_shape=image_shape,
            lr=0.1, dtype=dtype,
        )
    state = init_state(model["startup"])
    step = program_to_fn(model["main"], [model["loss"]], return_state=True)
    jitted = _jit_step(step, "resnet50_images_per_sec_per_chip")

    rng = np.random.RandomState(0)
    x = rng.randn(batch, *image_shape).astype(np.float32)
    if dtype == "bfloat16":
        import jax.numpy as jnp

        x = jnp.asarray(x, dtype=jnp.bfloat16)
    y = rng.randint(0, 1000, size=(batch, 1)).astype(np.int64)
    feeds = {"data": jax.device_put(x), "label": jax.device_put(y)}

    iters = 30 if on_tpu else 3
    dt, _ = _time_steps(jitted, state, feeds, iters)
    ips = batch * iters / dt

    # ResNet-50 fwd ≈ 3.8 GFLOPs/img @224²; training (fwd + dgrad + wgrad) ≈ 3×
    train_flops_per_img = 3 * 3.8e9
    out = {
        "metric": "resnet50_images_per_sec_per_chip",
        "value": round(ips, 2),
        "unit": "images/sec",
        "vs_baseline": round(ips / BASELINE_IMAGES_PER_SEC, 3),
    }
    if on_tpu:
        out["mfu"] = round(ips * train_flops_per_img / V5E_PEAK_BF16_FLOPS, 4)
    return out


def bench_resnet_real_input(on_tpu, synthetic_ips):
    """ResNet-50 fed by the REAL input path (jpeg corpus -> pre-decoded
    uint8 recordio -> C++ shuffling loader -> crop/flip -> normalize
    on-device), vs the synthetic-feed number: proves whether input is the
    bottleneck (VERDICT r3 item 5).

    Normalization/cast runs inside the jitted step (fuses into the first
    conv) so the host ships uint8 — 4x less host RAM and host->device
    bandwidth, which matters doubly through the axon tunnel."""
    import tempfile

    import jax
    import jax.numpy as jnp

    import paddle_tpu as fluid
    from paddle_tpu.jax_bridge import init_state, program_to_fn
    from paddle_tpu.models import resnet
    from paddle_tpu.reader.image_pipeline import (
        convert_decoded_to_recordio,
        decoded_pipeline,
        batched_images,
        synthesize_jpeg_corpus,
        IMG_MEAN,
        IMG_STD,
    )

    batch = 128 if on_tpu else 8
    dtype = "bfloat16" if on_tpu else "float32"
    n_corpus = 512 if on_tpu else 64
    iters = 24 if on_tpu else 2

    d = tempfile.mkdtemp(prefix="bench_imgs_")
    samples = synthesize_jpeg_corpus(d, n=n_corpus, size=256, classes=1000, seed=0)
    shards = convert_decoded_to_recordio(samples, os.path.join(d, "dec"), num_shards=4)

    with fluid.unique_name.guard():
        model = resnet.get_model(
            batch_size=batch, class_dim=1000, depth=50, image_shape=(3, 224, 224),
            lr=0.1, dtype=dtype,
        )
    state = init_state(model["startup"])
    raw_step = program_to_fn(model["main"], [model["loss"]], return_state=True)
    mean = jnp.asarray(IMG_MEAN)
    std = jnp.asarray(IMG_STD)
    cdtype = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32

    def step(state, feeds):
        x = feeds["data"].astype(jnp.float32) / 255.0
        x = ((x - mean[None]) / std[None]).astype(cdtype)
        return raw_step(state, {"data": x, "label": feeds["label"]})

    jitted = jax.jit(step, donate_argnums=(0,))

    # infinite-epoch pipeline through the shared async device-feed
    # machinery (reader.device_prefetch).  SEVERAL transfer threads keep
    # device_put ahead of the compute stream: through the axon tunnel
    # each put pays an RPC round trip, so a single prefetch thread
    # serializes latency·batches; concurrent puts pipeline it (double
    # buffering covers plain PCIe hosts too).  The prefetcher serializes
    # next() on the source (host-side decode/slice is not thread-safe)
    # while transfers run unlocked, and close() drains/joins the threads
    # deadline-capped so later (memory-hungry) legs never run with ~7
    # batches still pinned on device.
    from paddle_tpu.reader.device_prefetch import DevicePrefetcher

    reader = decoded_pipeline(shards, mode="train", image_size=224,
                              epochs=10_000, output="uint8")
    batches = batched_images(reader, batch)()

    def to_device(pair):
        imgs, labels = pair
        # int64 labels, same as the synthetic leg: a differing label
        # dtype would trace a second program and the two legs would
        # no longer measure the same compiled step
        return {"data": jax.device_put(imgs),
                "label": jax.device_put(labels.astype(np.int64))}

    feeds = DevicePrefetcher(batches, to_device, buffer_size=4,
                             transfer_threads=3)
    try:
        for _ in range(3):  # warmup/compile
            fetches, state = jitted(state, next(feeds))
        np.asarray(fetches[0])
        t0 = time.perf_counter()
        for _ in range(iters):
            fetches, state = jitted(state, next(feeds))
        np.asarray(fetches[0])
        dt = time.perf_counter() - t0
        ips = batch * iters / dt
    finally:
        # release the transfer threads and their pinned device batches on
        # the error path too; a thread wedged inside a device_put RPC is
        # abandoned at the shutdown deadline (daemon threads die with the
        # process anyway)
        feeds.close()

    return {
        "metric": "resnet50_real_input_images_per_sec_per_chip",
        "value": round(ips, 2),
        "unit": "images/sec",
        "vs_baseline": round(ips / BASELINE_IMAGES_PER_SEC, 3),
        "input_fraction_of_synthetic": round(ips / synthetic_ips, 3) if synthetic_ips else None,
    }


def bench_resnet_inference(on_tpu):
    """ResNet-50 forward-only throughput, bf16 vs int8 execution
    (contrib.quantize.Int8InferenceTranspiler): the MXU's int8 path runs
    2x the bf16 MAC rate on v5e, so int8 inference is the perf ceiling
    check for the quantized stack."""
    import jax

    import paddle_tpu as fluid
    from paddle_tpu.contrib.quantize import Int8InferenceTranspiler
    from paddle_tpu.jax_bridge import init_state, program_to_fn
    from paddle_tpu.models.resnet import resnet_imagenet

    batch = 256 if on_tpu else 8
    dtype = "float32"  # weights f32; activations cast per mode below

    with fluid.unique_name.guard():
        main = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(main, startup):
            image = fluid.layers.data(name="data", shape=[3, 224, 224], dtype=dtype)
            predict = resnet_imagenet(image, class_dim=1000, depth=50, is_train=False)
        infer = main.clone(for_test=True)
    state = init_state(startup)

    rng = np.random.RandomState(0)
    x = rng.randn(batch, 3, 224, 224).astype(np.float32)
    iters = 30 if on_tpu else 2

    def run(prog, st, tag):
        import jax.numpy as jnp

        fn = program_to_fn(prog, [predict], is_test=True)
        # BOTH legs run bf16 activations and bf16 non-quantized params —
        # otherwise the int8 leg pays f32 bandwidth on every
        # BN/relu/pool/residual op and the speedup conflates dtype traffic
        # with the MXU int8 path it is meant to certify (int8 weights and
        # their f32 scales keep their dtypes)
        stc = {k: (jnp.asarray(v, jnp.bfloat16)
                   if hasattr(v, "dtype") and v.dtype == np.float32
                   and not k.endswith(".scale") else v)
               for k, v in st.items()}
        xx = jnp.asarray(x, jnp.bfloat16)
        jitted = jax.jit(fn)
        out = jitted(stc, {"data": xx})
        np.asarray(out[0][0, 0])
        t0 = time.perf_counter()
        for _ in range(iters):
            out = jitted(stc, {"data": xx})
        np.asarray(out[0][0, 0])
        return batch * iters / (time.perf_counter() - t0)

    ips_bf16 = run(infer, dict(state), "bf16")

    class _Scope(dict):
        pass

    s = _Scope(state)
    Int8InferenceTranspiler().transpile(infer, s)
    state_q = dict(state)
    state_q.update({k: np.asarray(v) for k, v in s.items()
                    if k.endswith((".int8", ".scale"))})
    ips_int8 = run(infer, state_q, "int8")

    return {
        "metric": "resnet50_int8_infer_images_per_sec_per_chip",
        "value": round(ips_int8, 2),
        "unit": "images/sec",
        "vs_baseline": None,
        "bf16_infer_images_per_sec": round(ips_bf16, 2),
        "int8_speedup_vs_bf16": round(ips_int8 / ips_bf16, 3) if ips_bf16 else None,
    }


def _transformer_train_flops_per_step(batch, seq, n_layer, d, d_inner, vocab):
    """Analytic matmul FLOPs for one training step (2·m·n·k per matmul,
    backward ≈ 2× forward)."""
    qkvo = 8 * d * d            # 4 projections per attention
    attn = 4 * seq * d          # scores + context per token
    ffn = 4 * d * d_inner
    enc = n_layer * (qkvo + attn + ffn)
    dec = n_layer * (2 * (qkvo + attn) + ffn)   # self + cross attention
    logits = 2 * d * vocab
    fwd = batch * seq * (enc + dec + logits)
    return 3 * fwd


def bench_transformer(on_tpu, batch=None, seq=None, metric="transformer_tokens_per_sec_per_chip", iters=30, baseline=BASELINE_TOKENS_PER_SEC):
    import jax

    import paddle_tpu as fluid
    from paddle_tpu.jax_bridge import init_state, program_to_fn
    from paddle_tpu.models import transformer as T

    # Transformer-base, WMT-scale vocab, bf16 on TPU, flash attention path.
    if batch is None or seq is None:
        batch, seq = (64, 256) if on_tpu else (2, 16)
    n_layer, n_head, d_model, d_inner = (6, 8, 512, 2048) if on_tpu else (2, 2, 32, 64)
    vocab = 30000 if on_tpu else 64

    with fluid.unique_name.guard():
        model = T.get_model(
            batch_size=batch, seq_len=seq, src_vocab_size=vocab, trg_vocab_size=vocab,
            max_length=seq, n_layer=n_layer, n_head=n_head, d_model=d_model,
            d_inner=d_inner, dropout=0.1, use_flash=on_tpu,
        )
    state = init_state(model["startup"])
    if on_tpu:
        import jax.numpy as jnp

        state = {
            k: (jnp.asarray(v, jnp.bfloat16) if hasattr(v, "dtype") and v.dtype == np.float32 else v)
            for k, v in state.items()
        }
    step = program_to_fn(model["main"], [model["loss"]], return_state=True)
    jitted = _jit_step(step, metric)

    rng = np.random.RandomState(0)
    feeds = {
        name: jax.device_put(rng.randint(1, vocab, size=(batch, seq)).astype(np.int64))
        for name in ("src_word", "trg_word", "lbl_word")
    }

    iters = iters if on_tpu else 3
    dt, _ = _time_steps(jitted, state, feeds, iters)
    tps = batch * seq * iters / dt  # target tokens/sec

    out = {
        "metric": metric,
        "value": round(tps, 2),
        "unit": "tokens/sec",
    }
    if baseline is not None:  # no published reference number for some shapes
        out["vs_baseline"] = round(tps / baseline, 3)
    if on_tpu:
        flops = _transformer_train_flops_per_step(batch, seq, n_layer, d_model, d_inner, vocab)
        out["mfu"] = round((flops / (batch * seq)) * tps / V5E_PEAK_BF16_FLOPS, 4)
    return out


def main():
    result = {"metric": "resnet50_images_per_sec_per_chip", "value": 0.0,
              "unit": "images/sec", "vs_baseline": 0.0}
    try:
        _, on_tpu = _init_backend()
    except Exception as e:  # noqa: BLE001
        result["error"] = "backend init: %s" % e
        print(json.dumps(result))
        return

    try:
        result = bench_resnet(on_tpu)
    except Exception as e:  # noqa: BLE001
        result["error"] = "%s: %s" % (type(e).__name__, e)
        traceback.print_exc(file=sys.stderr)

    extras = []
    try:
        extras.append(bench_resnet_real_input(on_tpu, result.get("value") or 0.0))
    except Exception as e:  # noqa: BLE001
        extras.append({
            "metric": "resnet50_real_input_images_per_sec_per_chip",
            "value": 0.0, "unit": "images/sec", "vs_baseline": 0.0,
            "error": "%s: %s" % (type(e).__name__, e)})
        traceback.print_exc(file=sys.stderr)
    try:
        extras.append(bench_resnet_inference(on_tpu))
    except Exception as e:  # noqa: BLE001
        extras.append({
            "metric": "resnet50_int8_infer_images_per_sec_per_chip",
            "value": 0.0, "unit": "images/sec", "vs_baseline": 0.0,
            "error": "%s: %s" % (type(e).__name__, e)})
        traceback.print_exc(file=sys.stderr)
    for kwargs in (
        {},  # Transformer-base headline config (batch 64, seq 256)
        # long-context configs: flash attention's O(T) HBM advantage compounds;
        # no reference baseline exists for these shapes (vs_baseline omitted).
        # At seq=2048 the fused one-grid Pallas backward auto-engages (23%
        # faster than the scan engine on-chip); at seq>=4096 its [T,
        # block_k] f32 score intermediates blow the 16M/core scoped-VMEM
        # limit so auto falls back to scan (parallel/flash_attention.py
        # FLASH_BWD_IMPL="auto", round-5 sweep in PERF.md).
        {"batch": 16, "seq": 1024, "baseline": None,
         "metric": "transformer_seq1024_tokens_per_sec_per_chip", "iters": 15},
        {"batch": 8, "seq": 2048, "baseline": None,
         "metric": "transformer_seq2048_tokens_per_sec_per_chip", "iters": 12},
        {"batch": 4, "seq": 4096, "baseline": None,
         "metric": "transformer_seq4096_tokens_per_sec_per_chip", "iters": 10},
    ):
        if kwargs and not on_tpu:
            continue  # long-seq config is TPU-only (too slow on CPU fallback)
        try:
            extras.append(bench_transformer(on_tpu, **kwargs))
        except Exception as e:  # noqa: BLE001
            extras.append({
                "metric": kwargs.get("metric", "transformer_tokens_per_sec_per_chip"),
                "value": 0.0, "unit": "tokens/sec", "vs_baseline": 0.0,
                "error": "%s: %s" % (type(e).__name__, e)})
            traceback.print_exc(file=sys.stderr)
    result["extra_metrics"] = extras

    print(json.dumps(result))


if __name__ == "__main__":
    main()
